"""Dataset: lazy, distributed, block-based data transforms.

The reference's ``ray.data.Dataset`` (python/ray/data/dataset.py:124 —
``map:214``, ``map_batches:307``, plus repartition/random_shuffle/sort/
split/zip/groupby/iter_batches/write_*). Same lazy-plan design over the
TPU-native runtime: blocks are store objects (tensor blocks stay
contiguous and zero-copy), per-block transforms are tasks (or warm-actor
pools), and ``iter_batches`` is the per-host input pipeline that feeds
jax device_put — the role Ray Data plays for Ray Train.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

from .. import api
from .block import (
    BlockAccessor, BlockMetadata, DelegatingBlockBuilder, batch_to_block,
    concat_blocks,
)
from .plan import (
    ActorPoolStrategy, AllToAllStage, BlockList, ExecutionPlan, OneToOneStage,
)
from . import shuffle as _shuffle


class Dataset:
    def __init__(self, plan: ExecutionPlan):
        self._plan = plan

    # ------------------------------------------------------------ transforms
    def map(self, fn: Callable[[Any], Any], *,
            compute: Any = "tasks") -> "Dataset":
        def block_fn(block):
            builder = DelegatingBlockBuilder()
            for row in BlockAccessor.for_block(block).iter_rows():
                builder.add(fn(row))
            return builder.build()

        return self._with_stage(OneToOneStage("map", block_fn, compute))

    def flat_map(self, fn: Callable[[Any], List[Any]], *,
                 compute: Any = "tasks") -> "Dataset":
        def block_fn(block):
            builder = DelegatingBlockBuilder()
            for row in BlockAccessor.for_block(block).iter_rows():
                for out in fn(row):
                    builder.add(out)
            return builder.build()

        return self._with_stage(OneToOneStage("flat_map", block_fn, compute))

    def filter(self, fn: Callable[[Any], bool], *,
               compute: Any = "tasks") -> "Dataset":
        def block_fn(block):
            builder = DelegatingBlockBuilder()
            for row in BlockAccessor.for_block(block).iter_rows():
                if fn(row):
                    builder.add(row)
            return builder.build()

        return self._with_stage(OneToOneStage("filter", block_fn, compute))

    def map_batches(self, fn: Callable[[Any], Any], *,
                    batch_size: Optional[int] = 4096,
                    batch_format: str = "default",
                    compute: Any = "tasks",
                    **fn_kwargs) -> "Dataset":
        """Apply fn to batches (reference dataset.py:307). The hot path for
        TPU preprocessing: with batch_format='numpy' the batch is a
        contiguous ndarray (or dict of them) ready for vectorized ops."""

        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            size = batch_size or max(n, 1)
            builder = DelegatingBlockBuilder()
            for start in range(0, max(n, 1), size):
                if n == 0:
                    break
                end = min(start + size, n)
                piece = acc.slice(start, end)
                batch = BlockAccessor.for_block(piece).to_batch(batch_format)
                out = fn(batch, **fn_kwargs) if fn_kwargs else fn(batch)
                builder.add_block(batch_to_block(out))
            return builder.build()

        return self._with_stage(
            OneToOneStage("map_batches", block_fn, compute))

    def add_column(self, col: str, fn: Callable[[Any], Any]) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            batch = acc.to_batch("pandas")
            batch[col] = fn(batch)
            return batch

        return self._with_stage(OneToOneStage("add_column", block_fn))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda df: df.drop(columns=cols), batch_format="pandas")

    # ------------------------------------------------------------ all-to-all
    def repartition(self, num_blocks: int, *,
                    shuffle: bool = False) -> "Dataset":
        if shuffle:
            return self._with_stage(AllToAllStage(
                "repartition", _shuffle.random_shuffle_stage(
                    None, num_blocks)))
        return self._with_stage(AllToAllStage(
            "repartition", _shuffle.repartition_stage(num_blocks)))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        return self._with_stage(AllToAllStage(
            "random_shuffle", _shuffle.random_shuffle_stage(
                seed, num_blocks)))

    def sort(self, key: Union[str, Callable, None] = None,
             descending: bool = False) -> "Dataset":
        return self._with_stage(AllToAllStage(
            "sort", _shuffle.sort_stage(key, descending)))

    def groupby(self, key: Union[str, Callable]) -> "GroupedData":
        return GroupedData(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        """Zip blocks row-aligned with another dataset (dataset.py zip)."""
        my_blocks = self._plan.execute()
        other_blocks = other._plan.execute()
        n_rows = sum(m.num_rows or 0 for _, m in my_blocks)
        o_rows = sum(m.num_rows or 0 for _, m in other_blocks)
        if n_rows != o_rows:
            raise ValueError(
                f"zip requires equal row counts: {n_rows} vs {o_rows}")
        # each task receives only the other-side blocks overlapping its
        # row range (offset rebased by overlapping_blocks)
        out_refs = []
        offset = 0
        for ref, meta in my_blocks:
            count = meta.num_rows or 0
            lo, _hi, rows, orefs = _shuffle.overlapping_blocks(
                other_blocks, offset, offset + count)
            block_ref, meta_ref = _zip_slice.options(num_returns=2).remote(
                ref, lo, count, rows, *orefs)
            out_refs.append((block_ref, meta_ref))
            offset += count
        blocks = [(b, api.get(m)) for b, m in out_refs]
        return Dataset(ExecutionPlan(blocks, stats=self._plan.stats))

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self._plan.execute())
        for o in others:
            blocks.extend(o._plan.execute())
        return Dataset(ExecutionPlan(blocks, stats=self._plan.stats))

    # ------------------------------------------------------------ consuming
    def num_blocks(self) -> int:
        return len(self._plan.execute())

    def count(self) -> int:
        return sum(m.num_rows or 0 for _, m in self._plan.execute())

    def size_bytes(self) -> int:
        return sum(m.size_bytes or 0 for _, m in self._plan.execute())

    def schema(self) -> Any:
        blocks = self._plan.execute()
        return blocks[0][1].schema if blocks else None

    def input_files(self) -> List[str]:
        files: List[str] = []
        for _, m in self._plan.execute():
            files.extend(m.input_files)
        return sorted(set(files))

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            # rmtcheck: disable=log-discipline — show() IS console output
            print(row)

    def limit(self, limit: int) -> "Dataset":
        blocks = self._plan.execute()
        kept: BlockList = []
        remaining = limit
        for ref, meta in blocks:
            if remaining <= 0:
                break
            n = meta.num_rows or 0
            if n <= remaining:
                kept.append((ref, meta))
                remaining -= n
            else:
                block_ref, meta_ref = _truncate.options(
                    num_returns=2).remote(ref, remaining)
                kept.append((block_ref, api.get(meta_ref)))
                remaining = 0
        return Dataset(ExecutionPlan(kept, stats=self._plan.stats))

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "default",
                     prefetch_blocks: int = 1,
                     drop_last: bool = False) -> Iterator[Any]:
        """Stream batches; blocks are prefetched with wait() ahead of use
        (the per-host input pipeline; reference dataset.py iter_batches)."""
        carry = None
        for block in self._iter_blocks(prefetch=prefetch_blocks):
            if carry is not None:
                block = concat_blocks([carry, block])
                carry = None
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if batch_size is None:
                yield acc.to_batch(batch_format)
                continue
            start = 0
            while start + batch_size <= n:
                piece = acc.slice(start, start + batch_size)
                yield BlockAccessor.for_block(piece).to_batch(batch_format)
                start += batch_size
            if start < n:
                carry = acc.slice(start, n)
        if carry is not None and not drop_last:
            yield BlockAccessor.for_block(carry).to_batch(batch_format)

    def _iter_blocks(self, prefetch: int = 1) -> Iterator[Any]:
        """Stream blocks with real read-ahead: a fetch thread resolves the
        next ``prefetch`` blocks (waiting on their producing tasks and
        mapping/deserializing them) while the caller consumes the current
        one — ingest/compute overlap for the step loop."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        blocks = self._plan.execute()
        refs = [ref for ref, _ in blocks]
        if not refs:
            return
        depth = max(1, prefetch)
        ex = ThreadPoolExecutor(1, thread_name_prefix="data-prefetch")
        try:
            futs = deque(ex.submit(api.get, r) for r in refs[:depth])
            next_i = len(futs)
            while futs:
                block = futs.popleft().result()
                if next_i < len(refs):
                    futs.append(ex.submit(api.get, refs[next_i]))
                    next_i += 1
                yield block
        finally:
            ex.shutdown(wait=False, cancel_futures=True)

    def split(self, n: int, *, equal: bool = False,
              locality_hints=None) -> List["Dataset"]:
        """Split into n datasets by block (reference dataset.py split);
        equal=True rebalances row counts exactly."""
        blocks = self._plan.execute()
        if equal:
            per = self.count() // n
            return self.split_at_indices([per * i for i in range(1, n)])
        out: List[List] = [[] for _ in range(n)]
        for i, bm in enumerate(blocks):
            out[i % n].append(bm)
        return [Dataset(ExecutionPlan(b, stats=self._plan.stats))
                for b in out]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        blocks = self._plan.execute()
        total = sum(m.num_rows or 0 for _, m in blocks)
        prev = 0
        pieces: List[Dataset] = []
        for idx in list(indices) + [total]:
            lo, hi, rows, refs = _shuffle.overlapping_blocks(
                blocks, prev, idx)
            block_ref, meta_ref = _shuffle._slice_range.options(
                num_returns=2).remote(lo, hi, rows, *refs)
            pieces.append(Dataset(ExecutionPlan(
                [(block_ref, api.get(meta_ref))], stats=self._plan.stats)))
            prev = idx
        return pieces

    # ------------------------------------------------------------ aggregates
    def sum(self, on: Optional[str] = None):
        return self._agg(np.sum, on)

    def min(self, on: Optional[str] = None):
        return self._agg(np.min, on)

    def max(self, on: Optional[str] = None):
        return self._agg(np.max, on)

    def mean(self, on: Optional[str] = None):
        total = self._agg(np.sum, on)
        n = self.count()
        return total / n if n else None

    def std(self, on: Optional[str] = None):
        vals = np.asarray(self._column_values(on))
        return float(np.std(vals, ddof=1)) if len(vals) > 1 else 0.0

    def _column_values(self, on: Optional[str]):
        vals: List[Any] = []
        for row in self.iter_rows():
            vals.append(row[on] if on is not None else row)
        return vals

    def _agg(self, op, on: Optional[str]):
        refs = [_block_agg.remote(ref, op, on)
                for ref, _ in self._plan.execute()]
        parts = [p for p in api.get(refs) if p is not None]
        if not parts:
            return None
        result = op(np.asarray(parts))
        return result.item() if hasattr(result, "item") else result

    # ------------------------------------------------------------ conversion
    def to_numpy(self, column: Optional[str] = None):
        batches = list(self.iter_batches(batch_size=None,
                                         batch_format="numpy"))
        if not batches:
            return np.array([])
        if isinstance(batches[0], dict):
            merged = {k: np.concatenate([b[k] for b in batches])
                      for k in batches[0]}
            return merged[column] if column else merged
        return np.concatenate(batches)

    def to_pandas(self):
        import pandas as pd

        frames = list(self.iter_batches(batch_size=None,
                                        batch_format="pandas"))
        return pd.concat(frames, ignore_index=True) if frames else \
            pd.DataFrame()

    def to_jax(self, column: Optional[str] = None, *, device=None):
        """Materialize as a jax.Array (device_put of the contiguous numpy
        form) — the TPU-native terminal op."""
        import jax

        arr = self.to_numpy(column)
        if isinstance(arr, dict):
            return {k: jax.device_put(v, device) for k, v in arr.items()}
        return jax.device_put(arr, device)

    def materialize(self) -> "Dataset":
        self._plan.execute()
        return self

    fully_executed = materialize

    def window(self, *, blocks_per_window: int = 10):
        from .pipeline import DatasetPipeline

        return DatasetPipeline.from_dataset(
            self, blocks_per_window=blocks_per_window)

    def repeat(self, times: Optional[int] = None):
        """Repeat the dataset ``times`` epochs; no argument = infinite
        (reference dataset.py repeat semantics)."""
        from .pipeline import DatasetPipeline

        return DatasetPipeline.from_dataset(
            self, blocks_per_window=max(1, self.num_blocks()),
            repeat=-1 if times is None else times)

    # --------------------------------------------------------------- writes
    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json")

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet")

    def _write(self, path: str, fmt: str) -> List[str]:
        import os

        os.makedirs(path, exist_ok=True)
        blocks = self._plan.execute()
        refs = [_write_block.remote(ref, path, fmt, i)
                for i, (ref, _) in enumerate(blocks)]
        return api.get(refs)

    # ---------------------------------------------------------------- misc
    def stats(self) -> str:
        return self._plan.stats.summary()

    def _with_stage(self, stage) -> "Dataset":
        return Dataset(self._plan.with_stage(stage))

    def __repr__(self):
        if self._plan.has_lazy_stages():
            return "Dataset(lazy)"
        blocks = self._plan.execute()
        return (f"Dataset(num_blocks={len(blocks)}, "
                f"num_rows={self.count()}, schema={self.schema()})")


class GroupedData:
    """Sort/hash-free hash aggregation (reference data/grouped_dataset.py):
    map tasks partial-aggregate per block by key; the driver merges."""

    def __init__(self, ds: Dataset, key: Union[str, Callable]):
        self._ds = ds
        self._key = key

    def _key_fn(self) -> Callable:
        key = self._key
        return key if callable(key) else (lambda r: r[key])

    def count(self) -> Dict[Any, int]:
        return self._aggregate(lambda rows: len(rows))

    def sum(self, on: Optional[str] = None) -> Dict[Any, Any]:
        return self._aggregate(
            lambda rows: np.sum(_vals(rows, on)).item())

    def min(self, on: Optional[str] = None) -> Dict[Any, Any]:
        return self._aggregate(
            lambda rows: np.min(_vals(rows, on)).item())

    def max(self, on: Optional[str] = None) -> Dict[Any, Any]:
        return self._aggregate(
            lambda rows: np.max(_vals(rows, on)).item())

    def mean(self, on: Optional[str] = None) -> Dict[Any, Any]:
        sums = self._aggregate(
            lambda rows: np.sum(_vals(rows, on)).item())
        counts = self.count()
        return {k: sums[k] / counts[k] for k in sums}

    def map_groups(self, fn: Callable[[List[Any]], Any]) -> Dataset:
        groups = self._collect_groups()
        rows = [fn(v) for v in groups.values()]
        from .read_api import from_items

        return from_items(rows, parallelism=max(1, min(8, len(rows))))

    def _collect_groups(self) -> Dict[Any, List[Any]]:
        key_fn = self._key_fn()
        refs = [_group_block.remote(ref, key_fn)
                for ref, _ in self._ds._plan.execute()]
        merged: Dict[Any, List[Any]] = {}
        for part in api.get(refs):
            for k, rows in part.items():
                merged.setdefault(k, []).extend(rows)
        return merged

    def _aggregate(self, agg: Callable[[List[Any]], Any]) -> Dict[Any, Any]:
        return {k: agg(v) for k, v in sorted(
            self._collect_groups().items(), key=lambda kv: repr(kv[0]))}


def _vals(rows: List[Any], on: Optional[str]):
    return np.asarray([r[on] if on is not None else r for r in rows])


@api.remote
def _group_block(block, key_fn):
    groups: Dict[Any, List[Any]] = {}
    for row in BlockAccessor.for_block(block).iter_rows():
        groups.setdefault(key_fn(row), []).append(row)
    return groups


@api.remote
def _block_agg(block, op, on: Optional[str]):
    acc = BlockAccessor.for_block(block)
    if acc.num_rows() == 0:
        return None
    if on is not None:
        vals = np.asarray([r[on] for r in acc.iter_rows()])
    elif isinstance(block, np.ndarray):
        vals = block
    else:
        vals = np.asarray(list(acc.iter_rows()))
    return op(vals).item()


@api.remote
def _truncate(block, n: int):
    acc = BlockAccessor.for_block(block)
    piece = acc.slice(0, n)
    return piece, BlockAccessor.for_block(piece).get_metadata()


@api.remote
def _zip_slice(my_block, offset: int, count: int,
               other_rows: List[int], *other_blocks):
    """Pair rows [offset, offset+count) of the other dataset with
    my_block's rows."""
    from .shuffle import _rows_like

    other_sel: List[Any] = []
    pos = 0
    for nrows, blk in zip(other_rows, other_blocks):
        lo, hi = pos, pos + nrows
        pos = hi
        if hi <= offset or lo >= offset + count:
            continue
        s = max(offset - lo, 0)
        e = min(offset + count - lo, nrows)
        other_sel.extend(
            BlockAccessor.for_block(
                BlockAccessor.for_block(blk).slice(s, e)).iter_rows())
    rows = []
    for mine, theirs in zip(
            BlockAccessor.for_block(my_block).iter_rows(), other_sel):
        if isinstance(mine, dict) and isinstance(theirs, dict):
            merged = dict(mine)
            for k, v in theirs.items():
                merged[k if k not in merged else f"{k}_1"] = v
            rows.append(merged)
        else:
            rows.append((mine, theirs))
    block = rows
    return block, BlockAccessor.for_block(block).get_metadata()


@api.remote
def _write_block(block, path: str, fmt: str, index: int) -> str:
    import os

    acc = BlockAccessor.for_block(block)
    fname = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "csv":
        acc.to_pandas().to_csv(fname, index=False)
    elif fmt == "json":
        acc.to_pandas().to_json(fname, orient="records", lines=True)
    elif fmt == "parquet":
        acc.to_pandas().to_parquet(fname, index=False)
    else:
        raise ValueError(fmt)
    return fname
