"""Data library: distributed, block-based datasets for TPU input pipelines.

The reference's ``ray.data`` (python/ray/data/ — Dataset lazy plans,
block model, task/actor compute, push-based shuffle, DatasetPipeline).
"""

from .block import BlockAccessor, BlockMetadata  # noqa: F401
from .dataset import Dataset, GroupedData  # noqa: F401
from .pipeline import DatasetPipeline  # noqa: F401
from .plan import ActorPoolStrategy  # noqa: F401
from .read_api import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)
