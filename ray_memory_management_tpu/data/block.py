"""Block model: the unit of distributed data.

The reference's Dataset is a list of object-store blocks with driver-side
metadata (python/ray/data/block.py — Block, BlockMetadata, BlockAccessor;
blocks are arrow/pandas/simple-list). Here a block is one of five shapes,
chosen to keep tensors contiguous end-to-end (zero-copy through the shm
store into jax.device_put, no row-wise boxing):

  - list            — "simple" rows (any Python objects)
  - np.ndarray      — a tensor batch; row i is ``arr[i]``
  - dict[str, np.ndarray] — columnar tensor batch; row i is ``{k: v[i]}``
  - pandas.DataFrame
  - pyarrow.Table

``BlockAccessor.for_block`` dispatches on the runtime type, mirroring the
reference's accessor pattern (data/block.py BlockAccessor.for_block).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


def _pandas():
    import pandas as pd

    return pd


def _arrow():
    import pyarrow as pa

    return pa


@dataclass
class BlockMetadata:
    """Driver-side per-block stats (reference data/block.py BlockMetadata)."""

    num_rows: Optional[int]
    size_bytes: Optional[int]
    schema: Any = None
    input_files: List[str] = field(default_factory=list)
    exec_stats: Optional[dict] = None


class BlockAccessor:
    def __init__(self, block: Any):
        self._block = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        if isinstance(block, list):
            return SimpleBlockAccessor(block)
        if isinstance(block, np.ndarray):
            return NumpyBlockAccessor(block)
        if isinstance(block, dict):
            return NumpyDictBlockAccessor(block)
        type_name = type(block).__module__ + "." + type(block).__name__
        if "pandas" in type_name:
            return PandasBlockAccessor(block)
        if "pyarrow" in type_name:
            return ArrowBlockAccessor(block)
        raise TypeError(f"unsupported block type: {type(block)}")

    # interface ---------------------------------------------------------
    def num_rows(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def iter_rows(self) -> Iterator[Any]:
        raise NotImplementedError

    def slice(self, start: int, end: int) -> Any:
        raise NotImplementedError

    def schema(self) -> Any:
        raise NotImplementedError

    def to_batch(self, batch_format: str) -> Any:
        """Convert to the user-facing batch format: 'default'/'native' (the
        block itself), 'numpy' (ndarray or dict of ndarrays), 'pandas'."""
        if batch_format in ("default", "native"):
            return self._block
        if batch_format == "numpy":
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "pyarrow":
            return self.to_arrow()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def to_numpy(self):
        raise NotImplementedError

    def to_pandas(self):
        raise NotImplementedError

    def to_arrow(self):
        raise NotImplementedError

    def get_metadata(self, input_files: Optional[List[str]] = None,
                     exec_stats: Optional[dict] = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=input_files or [],
            exec_stats=exec_stats,
        )

    def sample(self, n: int, key=None) -> List[Any]:
        rows = list(self.iter_rows())
        if not rows:
            return []
        idx = np.random.default_rng(len(rows)).integers(
            0, len(rows), size=min(n, len(rows)))
        picked = [rows[i] for i in idx]
        if key is not None:
            picked = [key(r) for r in picked]
        return picked


class SimpleBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def size_bytes(self) -> int:
        import sys

        return sum(sys.getsizeof(r) for r in self._block[:100]) * max(
            1, len(self._block) // max(1, min(100, len(self._block))))

    def iter_rows(self):
        return iter(self._block)

    def slice(self, start, end):
        return self._block[start:end]

    def schema(self):
        return type(self._block[0]).__name__ if self._block else None

    def to_numpy(self):
        first = self._block[0] if self._block else None
        if isinstance(first, dict):
            keys = first.keys()
            return {k: np.asarray([r[k] for r in self._block]) for k in keys}
        return np.asarray(self._block)

    def to_pandas(self):
        pd = _pandas()
        first = self._block[0] if self._block else None
        if isinstance(first, dict):
            return pd.DataFrame(self._block)
        return pd.DataFrame({"value": self._block})

    def to_arrow(self):
        pa = _arrow()
        first = self._block[0] if self._block else None
        if isinstance(first, dict):
            return pa.Table.from_pylist(self._block)
        return pa.table({"value": self._block})


class NumpyBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def size_bytes(self) -> int:
        return int(self._block.nbytes)

    def iter_rows(self):
        return iter(self._block)

    def slice(self, start, end):
        return self._block[start:end]

    def schema(self):
        return f"ndarray{list(self._block.shape[1:])}:{self._block.dtype}"

    def to_numpy(self):
        return self._block

    def to_pandas(self):
        pd = _pandas()
        if self._block.ndim == 1:
            return pd.DataFrame({"value": self._block})
        return pd.DataFrame({"value": list(self._block)})

    def to_arrow(self):
        pa = _arrow()
        return pa.table({"value": self._block.tolist()})


class NumpyDictBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        if not self._block:
            return 0
        return len(next(iter(self._block.values())))

    def size_bytes(self) -> int:
        return int(sum(np.asarray(v).nbytes for v in self._block.values()))

    def iter_rows(self):
        n = self.num_rows()
        for i in range(n):
            yield {k: v[i] for k, v in self._block.items()}

    def slice(self, start, end):
        return {k: v[start:end] for k, v in self._block.items()}

    def schema(self):
        return {k: str(np.asarray(v).dtype) for k, v in self._block.items()}

    def to_numpy(self):
        return self._block

    def to_pandas(self):
        pd = _pandas()
        return pd.DataFrame({
            k: (v if np.asarray(v).ndim == 1 else list(v))
            for k, v in self._block.items()
        })

    def to_arrow(self):
        pa = _arrow()
        return pa.Table.from_pydict(
            {k: np.asarray(v).tolist() for k, v in self._block.items()})


class PandasBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def size_bytes(self) -> int:
        return int(self._block.memory_usage(deep=True).sum())

    def iter_rows(self):
        for _, row in self._block.iterrows():
            yield row.to_dict()

    def slice(self, start, end):
        return self._block.iloc[start:end]

    def schema(self):
        return {c: str(t) for c, t in self._block.dtypes.items()}

    def to_numpy(self):
        return {c: self._block[c].to_numpy() for c in self._block.columns}

    def to_pandas(self):
        return self._block

    def to_arrow(self):
        pa = _arrow()
        return pa.Table.from_pandas(self._block, preserve_index=False)


class ArrowBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return self._block.num_rows

    def size_bytes(self) -> int:
        return int(self._block.nbytes)

    def iter_rows(self):
        for row in self._block.to_pylist():
            yield row

    def slice(self, start, end):
        return self._block.slice(start, end - start)

    def schema(self):
        return self._block.schema

    def to_numpy(self):
        return {name: col.to_numpy(zero_copy_only=False)
                for name, col in zip(self._block.column_names,
                                     self._block.columns)}

    def to_pandas(self):
        return self._block.to_pandas()

    def to_arrow(self):
        return self._block


def batch_to_block(batch: Any) -> Any:
    """Normalize a user-returned batch into a block (reference
    data/_internal/output_buffer / batch conversions)."""
    if isinstance(batch, (list, np.ndarray)):
        return batch
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    return batch  # pandas / arrow pass through


def concat_blocks(blocks: List[Any]) -> Any:
    blocks = [b for b in blocks if BlockAccessor.for_block(b).num_rows() > 0]
    if not blocks:
        return []
    first = blocks[0]
    if len(blocks) == 1:
        return first
    if isinstance(first, list):
        out: List[Any] = []
        for b in blocks:
            out.extend(b)
        return out
    if isinstance(first, np.ndarray):
        return np.concatenate(blocks, axis=0)
    if isinstance(first, dict):
        keys = first.keys()
        return {k: np.concatenate([np.asarray(b[k]) for b in blocks], axis=0)
                for k in keys}
    type_name = type(first).__module__
    if "pandas" in type_name:
        pd = _pandas()
        return pd.concat(blocks, ignore_index=True)
    if "pyarrow" in type_name:
        pa = _arrow()
        return pa.concat_tables(blocks)
    raise TypeError(f"cannot concat block type {type(first)}")


class DelegatingBlockBuilder:
    """Accumulate rows/blocks and emit one block of the right shape
    (reference data/_internal/delegating_block_builder.py)."""

    def __init__(self):
        self._rows: List[Any] = []
        self._blocks: List[Any] = []

    def add(self, row: Any) -> None:
        self._rows.append(row)

    def add_block(self, block: Any) -> None:
        if self._rows:
            self._blocks.append(self._rows)
            self._rows = []
        self._blocks.append(block)

    def num_rows(self) -> int:
        n = len(self._rows)
        for b in self._blocks:
            n += BlockAccessor.for_block(b).num_rows()
        return n

    def build(self) -> Any:
        blocks = list(self._blocks)
        if self._rows:
            blocks.append(list(self._rows))
        if not blocks:
            return []
        return concat_blocks(blocks)
