"""All-to-all ops: repartition, random_shuffle, sort.

Shuffle is the reference's push-based two-stage design
(data/_internal/push_based_shuffle.py:330,348,363): map tasks split every
input block into R partition-pieces (one per reducer, returned as separate
store objects so each reducer pulls only its piece), reduce tasks concat
their pieces. Sort samples boundaries then range-partitions through the
same two-stage machinery (data/_internal/sort.py).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from .. import api
from .block import (
    BlockAccessor, BlockMetadata, DelegatingBlockBuilder, concat_blocks,
)
from .plan import BlockList


@api.remote
def _shuffle_map(block, n_reduce: int, seed: Optional[int], map_idx: int):
    """Split one block into n_reduce pieces, random permutation first."""
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    rng = np.random.default_rng(None if seed is None else seed + map_idx)
    perm = rng.permutation(n)
    bounds = np.linspace(0, n, n_reduce + 1).astype(int)
    pieces = []
    for r in range(n_reduce):
        idx = perm[bounds[r]:bounds[r + 1]]
        pieces.append(_take_rows(block, acc, idx))
    return tuple(pieces) if n_reduce > 1 else pieces[0]


@api.remote
def _partition_map(block, boundaries: List[Any], key: Callable):
    """Range-partition one block by sort key into len(boundaries)+1 pieces."""
    acc = BlockAccessor.for_block(block)
    rows = list(acc.iter_rows())
    keys = [key(r) for r in rows]
    order = np.argsort(np.asarray(keys, dtype=object), kind="stable") \
        if not _is_numeric(keys) else np.argsort(np.asarray(keys))
    sorted_idx = list(order)
    pieces: List[List[Any]] = [[] for _ in range(len(boundaries) + 1)]
    b = 0
    for i in sorted_idx:
        k = keys[i]
        while b < len(boundaries) and k >= boundaries[b]:
            b += 1
        pieces[b].append(rows[i])
    out = [_rows_like(block, acc, p) for p in pieces]
    return tuple(out) if len(out) > 1 else out[0]


@api.remote
def _shuffle_reduce(*pieces):
    block = concat_blocks(list(pieces))
    meta = BlockAccessor.for_block(block).get_metadata()
    return block, meta


@api.remote
def _sort_reduce(key_fn, *pieces):
    rows = []
    for p in pieces:
        rows.extend(BlockAccessor.for_block(p).iter_rows())
    rows.sort(key=key_fn)
    block = _rows_like(pieces[0] if pieces else [], None, rows)
    meta = BlockAccessor.for_block(block).get_metadata()
    return block, meta


def _is_numeric(keys) -> bool:
    return bool(keys) and isinstance(keys[0], (int, float, np.number))


def _take_rows(block, acc: BlockAccessor, idx):
    if isinstance(block, np.ndarray):
        return block[idx]
    if isinstance(block, dict):
        return {k: np.asarray(v)[idx] for k, v in block.items()}
    type_name = type(block).__module__
    if "pandas" in type_name:
        return block.iloc[idx]
    rows = list(acc.iter_rows())
    return [rows[i] for i in idx]


def _rows_like(template, acc, rows: List[Any]):
    """Rebuild a block from python rows, preserving tensor shape when the
    source was columnar."""
    if isinstance(template, np.ndarray) and rows:
        return np.asarray(rows)
    if isinstance(template, dict) and rows and isinstance(rows[0], dict):
        return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
    type_name = type(template).__module__
    if "pandas" in type_name and rows:
        import pandas as pd

        return pd.DataFrame(rows)
    return list(rows)


def random_shuffle_stage(seed: Optional[int], num_blocks: Optional[int]):
    def do(blocks: BlockList) -> BlockList:
        n_in = len(blocks)
        if n_in == 0:
            return blocks
        n_reduce = num_blocks or n_in
        piece_refs: List[List[Any]] = []
        for m, (ref, _meta) in enumerate(blocks):
            out = _shuffle_map.options(num_returns=n_reduce).remote(
                ref, n_reduce, seed, m)
            piece_refs.append(out if isinstance(out, list) else [out])
        result: BlockList = []
        out_refs = []
        for r in range(n_reduce):
            pieces = [piece_refs[m][r] for m in range(n_in)]
            block_ref, meta_ref = _shuffle_reduce.options(
                num_returns=2).remote(*pieces)
            out_refs.append((block_ref, meta_ref))
        for block_ref, meta_ref in out_refs:
            result.append((block_ref, api.get(meta_ref)))
        return result

    return do


def overlapping_blocks(blocks: BlockList, lo: int, hi: int):
    """Select only the input blocks whose rows intersect [lo, hi) and
    rebase the range onto their concatenation — each downstream task
    receives just the blocks it needs, not the whole dataset."""
    sel_rows: List[int] = []
    sel_refs: List[Any] = []
    offset = 0
    start = None
    for ref, m in blocks:
        n = m.num_rows or 0
        blo, bhi = offset, offset + n
        offset = bhi
        if bhi <= lo or blo >= hi or n == 0:
            continue
        if start is None:
            start = blo
        sel_rows.append(n)
        sel_refs.append(ref)
    if start is None:
        return 0, 0, [], []
    return lo - start, hi - start, sel_rows, sel_refs


def repartition_stage(num_blocks: int):
    """Split/merge to exactly num_blocks without a full shuffle (reference
    Dataset.repartition(shuffle=False): splits by target row counts)."""

    def do(blocks: BlockList) -> BlockList:
        if not blocks:
            return blocks
        total = sum(m.num_rows or 0 for _, m in blocks)
        bounds = np.linspace(0, total, num_blocks + 1).astype(int)
        # one task per output block slices its row range from the inputs
        out_refs = []
        for r in range(num_blocks):
            lo, hi, rows, refs = overlapping_blocks(
                blocks, int(bounds[r]), int(bounds[r + 1]))
            block_ref, meta_ref = _slice_range.options(
                num_returns=2).remote(lo, hi, rows, *refs)
            out_refs.append((block_ref, meta_ref))
        return [(b, api.get(m)) for b, m in out_refs]

    return do


@api.remote
def _slice_range(lo: int, hi: int, rows_per_block: List[int], *blocks):
    """Concatenate rows [lo, hi) of the logical dataset."""
    builder = DelegatingBlockBuilder()
    offset = 0
    for nrows, block in zip(rows_per_block, blocks):
        blo, bhi = offset, offset + nrows
        offset = bhi
        if bhi <= lo or blo >= hi:
            continue
        s, e = max(lo - blo, 0), min(hi - blo, nrows)
        builder.add_block(BlockAccessor.for_block(block).slice(s, e))
    block = builder.build()
    meta = BlockAccessor.for_block(block).get_metadata()
    return block, meta


def sort_stage(key: Optional[Callable], descending: bool = False):
    def do(blocks: BlockList) -> BlockList:
        if not blocks:
            return blocks
        key_fn = key if callable(key) else (
            (lambda r, k=key: r[k]) if key is not None else (lambda r: r))
        n_reduce = len(blocks)
        # sample boundaries from each block (sort.py sample_boundaries)
        sample_refs = [_sample_keys.remote(ref, key_fn)
                       for ref, _ in blocks]
        samples = sorted(s for part in api.get(sample_refs) for s in part)
        if samples and n_reduce > 1:
            step = len(samples) / n_reduce
            boundaries = [samples[int(step * i)]
                          for i in range(1, n_reduce)]
        else:
            boundaries = []
        piece_refs = []
        for ref, _meta in blocks:
            out = _partition_map.options(
                num_returns=len(boundaries) + 1).remote(
                ref, boundaries, key_fn)
            piece_refs.append(out if isinstance(out, list) else [out])
        out_refs = []
        for r in range(len(boundaries) + 1):
            pieces = [piece_refs[m][r] for m in range(len(blocks))]
            block_ref, meta_ref = _sort_reduce.options(
                num_returns=2).remote(key_fn, *pieces)
            out_refs.append((block_ref, meta_ref))
        result = [(b, api.get(m)) for b, m in out_refs]
        if descending:
            result = list(reversed(result))
            result = [(_reverse_block.remote(b), m) for b, m in result]
        return result

    return do


@api.remote
def _sample_keys(block, key_fn):
    return BlockAccessor.for_block(block).sample(5, key_fn)


@api.remote
def _reverse_block(block):
    acc = BlockAccessor.for_block(block)
    rows = list(acc.iter_rows())
    return _rows_like(block, acc, list(reversed(rows)))
