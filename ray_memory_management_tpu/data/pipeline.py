"""DatasetPipeline: windowed streaming execution.

The reference's pipelining layer (python/ray/data/dataset_pipeline.py +
_internal/pipeline_executor.py): a dataset is split into windows of
blocks; per-window transforms execute while earlier windows are being
consumed, overlapping ingest with compute — the input-pipeline shape that
keeps a TPU step loop fed.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterator, List, Optional

from .dataset import Dataset
from .plan import ExecutionPlan


class DatasetPipeline:
    def __init__(self, windows_fn: Callable[[], Iterator[Dataset]],
                 length: Optional[int] = None):
        self._windows_fn = windows_fn
        self._length = length
        self._consumed = False

    @staticmethod
    def from_dataset(ds: Dataset, *, blocks_per_window: int = 10,
                     repeat: Optional[int] = None) -> "DatasetPipeline":
        blocks = ds._plan.execute()
        windows: List[Dataset] = []
        for i in range(0, len(blocks), blocks_per_window):
            windows.append(Dataset(ExecutionPlan(
                blocks[i:i + blocks_per_window], stats=ds._plan.stats)))

        if repeat is None:
            def gen():
                return iter(windows)

            return DatasetPipeline(gen, length=len(windows))

        def gen_repeat():
            if repeat <= 0:  # infinite
                return (w for w in itertools.cycle(windows))
            return (w for _ in range(repeat) for w in windows)

        return DatasetPipeline(
            gen_repeat,
            length=None if repeat <= 0 else len(windows) * repeat)

    # per-window transforms: lazily applied as windows stream through
    def map(self, fn, **kwargs) -> "DatasetPipeline":
        return self._transform(lambda ds: ds.map(fn, **kwargs))

    def map_batches(self, fn, **kwargs) -> "DatasetPipeline":
        return self._transform(lambda ds: ds.map_batches(fn, **kwargs))

    def filter(self, fn, **kwargs) -> "DatasetPipeline":
        return self._transform(lambda ds: ds.filter(fn, **kwargs))

    def flat_map(self, fn, **kwargs) -> "DatasetPipeline":
        return self._transform(lambda ds: ds.flat_map(fn, **kwargs))

    def random_shuffle_each_window(self, *, seed=None) -> "DatasetPipeline":
        return self._transform(lambda ds: ds.random_shuffle(seed=seed))

    def repartition_each_window(self, n: int) -> "DatasetPipeline":
        return self._transform(lambda ds: ds.repartition(n))

    def _transform(self, f: Callable[[Dataset], Dataset]) -> "DatasetPipeline":
        prev = self._windows_fn

        def gen():
            return (f(w) for w in prev())

        return DatasetPipeline(gen, length=self._length)

    # consumption
    def iter_datasets(self) -> Iterator[Dataset]:
        self._mark_consumed()
        return iter(self._windows_fn())

    def iter_rows(self) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_batches(**kwargs)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self.iter_datasets())

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Split each window across n consumers (used per-host)."""
        base = self._windows_fn

        def make(idx: int) -> "DatasetPipeline":
            def gen():
                return (w.split(n)[idx] for w in base())

            return DatasetPipeline(gen, length=self._length)

        return [make(i) for i in range(n)]

    def num_windows(self) -> Optional[int]:
        return self._length

    def _mark_consumed(self) -> None:
        self._consumed = True
