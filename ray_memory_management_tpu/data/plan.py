"""Lazy execution plan: stages over distributed blocks.

The reference's ExecutionPlan (python/ray/data/_internal/plan.py:69,283)
holds input blocks plus a stage list; one-to-one stages fuse into a single
task per block, all-to-all stages (shuffle/sort/repartition) break fusion.
Same design here: ``OneToOneStage`` carries a block→block function chain
executed by ``_map_block_task`` (tasks) or a ``_BlockMapActor`` pool
(actor compute, reference data/_internal/compute.py:56,146).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Tuple

from .. import api
from .block import BlockAccessor, BlockMetadata

# (block object ref, metadata) — metadata rides inline, blocks stay remote
BlockRef = Any
BlockList = List[Tuple[BlockRef, BlockMetadata]]


@api.remote
def _map_block_task(fns: List[Callable], block):
    """Apply a fused chain of block transforms; returns (block, metadata).
    Runs remotely: the block arrives via the shm store (zero-copy for
    tensor blocks), the result is written back to the executing node's
    store."""
    t0 = time.time()
    for fn in fns:
        block = fn(block)
    meta = BlockAccessor.for_block(block).get_metadata(
        exec_stats={"wall_s": time.time() - t0})
    return block, meta


class _BlockMapActor:
    """Warm actor applying block transforms (ActorPoolStrategy compute)."""

    def ready(self):
        return "ok"

    def apply(self, fns: List[Callable], block):
        for fn in fns:
            block = fn(block)
        meta = BlockAccessor.for_block(block).get_metadata()
        return block, meta


class ActorPoolStrategy:
    """compute= option for map_batches (reference data/_internal/compute.py:146
    ActorPoolStrategy(min_size, max_size))."""

    def __init__(self, size: int = 2, max_size: Optional[int] = None,
                 num_tpus: float = 0, num_cpus: float = 1):
        self.size = size
        self.max_size = max_size or size
        self.num_tpus = num_tpus
        self.num_cpus = num_cpus


class Stage:
    name: str


class OneToOneStage(Stage):
    def __init__(self, name: str, block_fn: Callable[[Any], Any],
                 compute: Any = "tasks"):
        self.name = name
        self.block_fn = block_fn
        self.compute = compute

    def can_fuse_with(self, other: "Stage") -> bool:
        return (isinstance(other, OneToOneStage)
                and self.compute == "tasks" and other.compute == "tasks")


class AllToAllStage(Stage):
    def __init__(self, name: str,
                 fn: Callable[[BlockList], BlockList]):
        self.name = name
        self.fn = fn


class DatasetStats:
    def __init__(self):
        self.stages: List[Tuple[str, float, int]] = []  # name, wall, blocks

    def record(self, name: str, wall: float, num_blocks: int) -> None:
        self.stages.append((name, wall, num_blocks))

    def summary(self) -> str:
        lines = ["Dataset execution stats:"]
        for name, wall, nb in self.stages:
            lines.append(f"  stage {name}: {nb} blocks in {wall:.3f}s")
        return "\n".join(lines)


class ExecutionPlan:
    def __init__(self, blocks: BlockList, stages: Optional[List[Stage]] = None,
                 stats: Optional[DatasetStats] = None):
        self._in_blocks = blocks
        self._stages = list(stages or [])
        self._out_blocks: Optional[BlockList] = None
        self.stats = stats or DatasetStats()

    def with_stage(self, stage: Stage) -> "ExecutionPlan":
        # building on an executed plan chains from its output snapshot
        if self._out_blocks is not None:
            return ExecutionPlan(self._out_blocks, [stage], self.stats)
        return ExecutionPlan(self._in_blocks, self._stages + [stage],
                             self.stats)

    def has_lazy_stages(self) -> bool:
        return bool(self._stages) and self._out_blocks is None

    def execute(self) -> BlockList:
        if self._out_blocks is not None:
            return self._out_blocks
        blocks = self._in_blocks
        i = 0
        while i < len(self._stages):
            stage = self._stages[i]
            t0 = time.time()
            if isinstance(stage, OneToOneStage):
                # fuse the maximal run of fusable one-to-one stages
                fns = [stage.block_fn]
                names = [stage.name]
                while (i + 1 < len(self._stages)
                       and stage.can_fuse_with(self._stages[i + 1])):
                    i += 1
                    stage = self._stages[i]
                    fns.append(stage.block_fn)
                    names.append(stage.name)
                blocks = self._run_one_to_one(fns, blocks, stage.compute)
                self.stats.record("+".join(names), time.time() - t0,
                                  len(blocks))
            else:
                blocks = stage.fn(blocks)
                self.stats.record(stage.name, time.time() - t0, len(blocks))
            i += 1
        self._out_blocks = blocks
        return blocks

    def _run_one_to_one(self, fns: List[Callable], blocks: BlockList,
                        compute: Any) -> BlockList:
        if isinstance(compute, ActorPoolStrategy):
            return self._run_with_actors(fns, blocks, compute)
        out_refs = []
        for ref, _meta in blocks:
            block_ref, meta_ref = _map_block_task.options(
                num_returns=2).remote(fns, ref)
            out_refs.append((block_ref, meta_ref))
        return [(block_ref, api.get(meta_ref))
                for block_ref, meta_ref in out_refs]

    def _run_with_actors(self, fns: List[Callable], blocks: BlockList,
                         strategy: ActorPoolStrategy) -> BlockList:
        """Warm-actor compute: blocks round-robin over the pool; each
        actor's queue executes serially, so N actors process N blocks
        concurrently while results stay in the object store."""
        cls = api.remote(_BlockMapActor)
        opts = {"num_cpus": strategy.num_cpus}
        if strategy.num_tpus:
            opts["num_tpus"] = strategy.num_tpus
        n = min(strategy.size, max(1, len(blocks)))
        actors = [cls.options(**opts).remote() for _ in range(n)]
        api.get([a.ready.remote() for a in actors])
        try:
            out_refs = []
            for j, (ref, _meta) in enumerate(blocks):
                actor = actors[j % n]
                block_ref, meta_ref = actor.apply.options(
                    num_returns=2).remote(fns, ref)
                out_refs.append((block_ref, meta_ref))
            return [(b, api.get(m)) for b, m in out_refs]
        finally:
            for a in actors:
                api.kill(a)
