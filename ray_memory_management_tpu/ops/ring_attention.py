"""Ring attention: sequence/context parallelism over the ICI ring.

Net-new versus the reference (SURVEY.md §5 calls long-context support absent
there). Q, K, V are sharded along the sequence axis of a mesh; each step every
device attends its local Q block against the K/V chunk currently resident,
then rotates K/V one hop around the ring with ``lax.ppermute`` — after
``ring_size`` steps every Q block has seen every K/V chunk. Softmax is merged
online across steps (the same running max/denominator algebra as flash
attention), so the full attention matrix never materializes.

Causal masking works on global positions: chunk j's key offset is derived
from the originating device index, so masks stay exact as chunks rotate.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map

_NEG_INF = -1e30


def _chunk_attend(q, k, v, q_offset, k_offset, causal, scale, m, l, acc):
    """One flash-style accumulation step of q against one K/V chunk.

    q: [B, H, Sq, D]; k, v: [B, H, Sk, D]; m, l: [B, H, Sq, 1];
    acc: [B, H, Sq, D] fp32.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        Sq, Sk = q.shape[-2], k.shape[-2]
        rows = q_offset + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = k_offset + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(cols <= rows, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = True, scale: Optional[float] = None):
    """Attention over sequence-sharded [B, H, S, D] arrays.

    S is the GLOBAL sequence length; inputs are (or will be placed)
    sequence-sharded over ``axis``. Communication is one K/V-chunk ppermute
    per step — bandwidth-optimal on an ICI ring.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    ring = mesh.shape[axis]

    def body(q_loc, k_loc, v_loc):
        # q_loc/k_loc/v_loc: [B, H, S/ring, D] local shards
        idx = lax.axis_index(axis)
        S_loc = q_loc.shape[-2]
        q_offset = idx * S_loc
        B, H, _, D = q_loc.shape
        m = jnp.full((B, H, S_loc, 1), _NEG_INF, jnp.float32)
        l = jnp.zeros((B, H, S_loc, 1), jnp.float32)
        acc = jnp.zeros((B, H, S_loc, D), jnp.float32)
        perm = [(i, (i + 1) % ring) for i in range(ring)]

        def step(t, carry):
            m, l, acc, k_cur, v_cur = carry
            # the chunk now resident originated at device (idx - t) mod ring
            src = (idx - t) % ring
            k_offset = src * S_loc
            m, l, acc = _chunk_attend(
                q_loc, k_cur, v_cur, q_offset, k_offset, causal, scale,
                m, l, acc,
            )
            k_nxt = lax.ppermute(k_cur, axis, perm)
            v_nxt = lax.ppermute(v_cur, axis, perm)
            return m, l, acc, k_nxt, v_nxt

        m, l, acc, _, _ = lax.fori_loop(
            0, ring, step, (m, l, acc, k_loc, v_loc))
        return (acc / jnp.maximum(l, 1e-30)).astype(q_loc.dtype)

    spec = P(None, None, axis, None)
    mapped = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    return mapped(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = True, scale: Optional[float] = None):
    """Ulysses/DeepSpeed-style sequence parallelism: all-to-all re-shards
    from sequence-sharded to head-sharded, runs full-sequence attention
    locally per head group, and all-to-alls back. Complements ring attention:
    better when heads >> ring size and sequence chunks are small.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    ring = mesh.shape[axis]
    H = q.shape[1]
    if H % ring:
        raise ValueError(f"heads {H} must divide over axis size {ring}")

    def body(q_loc, k_loc, v_loc):
        # in: [B, H, S/ring, D] -> all-to-all -> [B, H/ring, S, D]
        def a2a(x, concat, split):
            return lax.all_to_all(x, axis, split_axis=split,
                                  concat_axis=concat, tiled=True)

        q_h = a2a(q_loc, 2, 1)  # gather seq, scatter heads
        k_h = a2a(k_loc, 2, 1)
        v_h = a2a(v_loc, 2, 1)
        from .flash_attention import reference_attention

        o_h = reference_attention(q_h, k_h, v_h, causal, scale)
        return a2a(o_h, 1, 2)  # back to sequence-sharded

    spec = P(None, None, axis, None)
    mapped = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    return mapped(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
