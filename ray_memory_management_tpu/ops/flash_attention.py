"""Flash attention as Pallas TPU kernels (forward AND backward), with a
pure-jnp fallback.

Net-new versus the reference (SURVEY.md §2.4: the reference has NO attention
kernels — GPU attention lives inside user torch code). Here the hot op is a
first-class TPU kernel:

  - forward: online-softmax blockwise attention. Grid is (BH, n_q, n_k): the
    K/V sequence streams through VMEM one (block_k, D) tile per grid step —
    VMEM stays O(block), so S is bounded by HBM, not VMEM. Running max /
    denominator / output accumulate in VMEM scratch across the innermost
    grid dimension; the logsumexp is saved for the backward in a (BH, S, 1)
    layout — blocks of (1, block_q, 1) are legal on TPU because the last
    block dim equals the array dim, so the per-row vector costs S fp32
    words, not a lane-replicated tile.
  - backward: two Pallas kernels, both O(block) VMEM: a dq kernel on grid
    (BH, n_q, n_k) and a dk/dv kernel on grid (BH, n_k, n_q), each
    recomputing the p tile from q, k and the saved lse (rematerialisation:
    trades one extra QK^T matmul for never materialising the S×S matrix —
    training memory is O(S·D), not O(S²)).
  - causal masking skips fully-masked tiles via pl.when on both passes, so
    the causal schedule does ~half the tile work.
  - CPU/testing: the same kernels run under interpret mode; tests compare
    against the jnp reference on a virtual device.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _pick_block(n: int, target: int) -> int:
    """Largest divisor of n that is <= target (TPU wants aligned blocks; for
    odd sizes we fall back to the full dimension)."""
    b = min(n, target)
    while n % b:
        b -= 1
    return b


def reference_attention(q, k, v, causal: bool = True,
                        scale: Optional[float] = None):
    """Plain jnp attention (the correctness oracle)."""
    *_, S, D = q.shape
    Skv = k.shape[-2]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(S)[:, None] + (Skv - S)
        ki = jnp.arange(Skv)[None, :]
        s = jnp.where(ki <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


def _causal_mask(s, qi, ki, block_q, block_k, off):
    """Mask the (block_q, block_k) score tile: col <= row + off survives
    (off = Skv - S supports cross/prefix attention like the reference)."""
    rows = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0) + off
    cols = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(cols <= rows, s, _NEG_INF)


# --------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, causal, scale, block_q, block_k, off):
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # a tile is fully masked iff its smallest col exceeds its largest row+off
    run_pred = (ki * block_k <= qi * block_q + (block_q - 1) + off
                if causal else True)

    @pl.when(run_pred)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == n_k - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = m_scr[:, :1] + jnp.log(l)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               save_lse=True):
    """Returns (out, lse) when save_lse else out; lse is (BH, S, 1) fp32.
    Inference callers pass save_lse=False so the kernel never writes the
    lse array (pallas outputs are not dead-code-eliminated)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Skv = k.shape[1]
    off = Skv - S
    block_q = _pick_block(S, block_q)
    block_k = _pick_block(Skv, block_k)
    grid = (BH, S // block_q, Skv // block_k)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k, off=off)
    if not save_lse:
        def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                   _inner=kernel):
            _inner(q_ref, k_ref, v_ref, o_ref, None, m_scr, l_scr, acc_scr)
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0))]
    if save_lse:
        out_shape.append(jax.ShapeDtypeStruct((BH, S, 1), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)))
    res = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return res if save_lse else res[0]


# -------------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, causal, scale, block_q, block_k, off):
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run_pred = (ki * block_k <= qi * block_q + (block_q - 1) + off
                if causal else True)

    @pl.when(run_pred)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)
        p = jnp.exp(s - lse)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] += lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, causal, scale, block_q, block_k, off):
    import jax.experimental.pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # fully masked iff the tile's largest row+off is below its smallest col
    run_pred = (qi * block_q + (block_q - 1) + off >= ki * block_k
                if causal else True)

    @pl.when(run_pred)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)
        p = jnp.exp(s - lse)  # (block_q, block_k)
        # dv += p^T @ do; dk += ds^T @ q — contract over the q rows so no
        # explicit transpose materialises
        dv_scr[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[...] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, causal, scale, block_q, block_k,
               interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Skv = k.shape[1]
    off = Skv - S
    block_q = _pick_block(S, block_q)
    block_k = _pick_block(Skv, block_k)
    # delta_i = rowsum(dO_i * O_i) — tiny elementwise pass XLA fuses
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[..., None]  # (BH, S, 1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, off=off),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(BH, S // block_q, Skv // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, off=off),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        grid=(BH, Skv // block_k, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(k, v, q, g, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------ public API
def _on_tpu() -> bool:
    """Is default computation placed on TPU? jax_default_device (set by CPU
    test harnesses) wins over the default backend, because compiled Pallas
    only lowers on the TPU backend."""
    try:
        dd = jax.config.jax_default_device
        if dd is not None:
            return dd.platform == "tpu"
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, scale, use_pallas, block_q, block_k):
    if use_pallas == "off":
        return reference_attention(q, k, v, causal, scale)
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                      interpret=(use_pallas == "interpret"), save_lse=False)


def _flash_fwd_rule(q, k, v, causal, scale, use_pallas, block_q, block_k):
    if use_pallas == "off":
        out = reference_attention(q, k, v, causal, scale)
        return out, (q, k, v, out, None)
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                          interpret=(use_pallas == "interpret"))
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, use_pallas, block_q, block_k,
                    residuals, g):
    q, k, v, out, lse = residuals
    if use_pallas == "off":
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_attention(q_, k_, v_, causal, scale),
            q, k, v)
        return vjp(g)
    return _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                      interpret=(use_pallas == "interpret"))


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    use_pallas: Optional[str] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """Multi-head attention over [B, H, S, D] (or [BH, S, D]) inputs.

    ``use_pallas``: "on" (compiled kernel), "interpret" (kernel under the
    Pallas interpreter — CPU testing), "off" (jnp reference), or None =
    auto: "on" when running on TPU, "off" elsewhere (interpret mode is too
    slow to be a default). Differentiable either way: the Pallas path uses
    the blockwise backward kernels.
    """
    if use_pallas is None:
        use_pallas = "on" if _on_tpu() else "off"
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    squeeze = q.ndim == 4
    if squeeze:
        B, H, S, D = q.shape
        qf = q.reshape(B * H, S, D)
        kf = k.reshape(B * H, k.shape[-2], D)
        vf = v.reshape(B * H, v.shape[-2], D)
    else:
        qf, kf, vf = q, k, v
    out = _flash_attention(qf, kf, vf, causal, scale, use_pallas,
                           block_q, block_k)
    return out.reshape(q.shape) if squeeze else out
