"""Flash attention as a Pallas TPU kernel, with a pure-jnp fallback.

Net-new versus the reference (SURVEY.md §2.4: the reference has NO attention
kernels — GPU attention lives inside user torch code). Here the hot op is a
first-class TPU kernel:

  - forward: online-softmax blockwise attention; Q blocks ride the grid, K/V
    stream through VMEM with a fori_loop; accumulators stay in fp32 while
    inputs can be bf16 (MXU-friendly).
  - backward: recompute-based custom VJP using the jnp reference (correct and
    memory-lean; a fused Pallas backward is a later-round optimization).
  - CPU/testing: the same kernel runs under interpret mode; tests compare it
    against the jnp reference on a virtual device.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _pick_block(n: int, target: int) -> int:
    """Largest divisor of n that is <= target (TPU wants aligned blocks; for
    odd sizes we fall back to the full dimension)."""
    b = min(n, target)
    while n % b:
        b -= 1
    return b


def reference_attention(q, k, v, causal: bool = True,
                        scale: Optional[float] = None):
    """Plain jnp attention (the correctness oracle)."""
    *_, S, D = q.shape
    Skv = k.shape[-2]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jnp.arange(S)[:, None] + (Skv - S)
        ki = jnp.arange(Skv)[None, :]
        s = jnp.where(ki <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal: bool, scale: float,
                block_q: int, block_k: int, kv_len: int):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, D)
    qi = pl.program_id(1)
    n_kb = kv_len // block_k

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    D = q.shape[-1]
    init = (
        jnp.full((block_q, 1), _NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
        jnp.zeros((block_q, D), jnp.float32),
    )
    m, l, acc = lax.fori_loop(0, n_kb, body, init)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, scale: float, block_q: int,
               block_k: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Skv = k.shape[1]
    block_q = _pick_block(S, block_q)
    block_k = _pick_block(Skv, block_k)
    grid = (BH, S // block_q)
    return pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, kv_len=Skv,
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Skv, D), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Skv, D), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(q, k, v)


def _on_tpu() -> bool:
    """Is default computation placed on TPU? jax_default_device (set by CPU
    test harnesses) wins over the default backend, because compiled Pallas
    only lowers on the TPU backend."""
    try:
        dd = jax.config.jax_default_device
        if dd is not None:
            return dd.platform == "tpu"
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, scale, use_pallas):
    if use_pallas == "off":
        return reference_attention(q, k, v, causal, scale)
    return _flash_fwd(q, k, v, causal, scale, block_q=256, block_k=256,
                      interpret=(use_pallas == "interpret"))


def _flash_fwd_rule(q, k, v, causal, scale, use_pallas):
    out = _flash_attention(q, k, v, causal, scale, use_pallas)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, use_pallas, residuals, g):
    # Recompute-based backward: differentiate the jnp reference (the
    # rematerialization trades FLOPs for HBM, the right TPU default)
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal, scale),
        q, k, v,
    )
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    use_pallas: Optional[str] = None):
    """Multi-head attention over [B, H, S, D] (or [BH, S, D]) inputs.

    ``use_pallas``: "on" (compiled kernel), "interpret" (kernel under the
    Pallas interpreter — CPU testing), "off" (jnp reference), or None =
    auto: "on" when running on TPU, "off" elsewhere (interpret mode is too
    slow to be a default).
    """
    if use_pallas is None:
        use_pallas = "on" if _on_tpu() else "off"
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    squeeze = q.ndim == 4
    if squeeze:
        B, H, S, D = q.shape
        qf = q.reshape(B * H, S, D)
        kf = k.reshape(B * H, k.shape[-2], D)
        vf = v.reshape(B * H, v.shape[-2], D)
    else:
        qf, kf, vf = q, k, v
    out = _flash_attention(qf, kf, vf, causal, scale, use_pallas)
    return out.reshape(q.shape) if squeeze else out
