"""TPU ops: Pallas kernels and sequence-parallel attention."""

from .flash_attention import flash_attention, reference_attention  # noqa: F401
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
