"""Mixture-of-Experts FFN with expert parallelism (EP).

Net-new versus the reference: SURVEY.md §2.4 lists expert parallelism as
absent there (no MoE anywhere in the snapshot) and marks it a net-new
target for this framework. The design is the GShard/Switch dense-dispatch
formulation, TPU-first:

  - routing, dispatch and combine are einsums over a STATIC capacity —
    no ragged shapes, no host control flow, everything jit-traceable and
    MXU-friendly;
  - expert weights carry a leading expert dim ([E, D, F]); under an
    ``ep`` mesh axis that dim is sharded one-expert-group-per-device and
    the dispatch/combine einsums lower to XLA all-to-alls over ICI
    (param_pspecs places the weights; with_sharding_constraint pins the
    per-expert buffers so GSPMD picks the all-to-all, not an all-gather);
  - top-k gating (k=1 Switch, k=2 GShard) with the standard
    load-balancing auxiliary loss (fraction-dispatched x mean-gate x E).

Capacity: each expert processes at most C = ceil(k * T / E) x
capacity_factor tokens per batch; overflow tokens fall through the
residual connection (their combine weights are zero), the Switch
"token dropping" behavior.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, n_layers: int, d_model: int, d_ff: int,
                    n_experts: int, param_dtype=jnp.float32):
    """Layer-stacked MoE FFN params: router + per-expert SwiGLU weights
    ([L, E, ...]); drop-in replacement for the dense w1/w3/w2 stack."""
    keys = jax.random.split(key, 4)
    L, D, F, E = n_layers, d_model, d_ff, n_experts

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, param_dtype) * (fan_in ** -0.5)

    return {
        "router": dense(keys[0], (L, D, E), D),
        "w1": dense(keys[1], (L, E, D, F), D),
        "w3": dense(keys[2], (L, E, D, F), D),
        "w2": dense(keys[3], (L, E, F, D), F),
    }


def capacity(group_size: int, n_experts: int, top_k: int,
             capacity_factor: float) -> int:
    return max(1, math.ceil(group_size * top_k / n_experts
                            * capacity_factor))


def _group_size(n_tokens: int, target: int) -> int:
    """Largest divisor of ``n_tokens`` that is <= target (GShard's group
    dimension: capacity scales with tokens-per-group, NOT total tokens, so
    the dispatch/combine tensors stay O(T * E * C_group) instead of the
    O(T^2)-ish blowup of one global group)."""
    g = min(n_tokens, max(1, target))
    while n_tokens % g != 0:
        g -= 1
    return g


def moe_ffn(x, layer, cfg, mesh: Optional[Mesh] = None):
    """MoE feed-forward: x [B, S, D] -> ([B, S, D], aux_loss scalar).

    ``layer`` holds this layer's slices: router [D, E], w1/w3 [E, D, F],
    w2 [E, F, D]. Gating/softmax run in fp32; expert matmuls in cfg.dtype
    (bf16 on the MXU). Tokens dispatch in groups of ~expert_group_size
    with per-group capacity (the GShard group dimension).
    """
    B, S, D = x.shape
    E = layer["router"].shape[-1]
    k = cfg.expert_top_k
    T = B * S
    g = _group_size(T, cfg.expert_group_size)
    G = T // g
    C = capacity(g, E, k, cfg.expert_capacity_factor)

    xg = x.reshape(G, g, D)
    logits = jnp.einsum("gtd,de->gte", xg.astype(jnp.float32),
                        layer["router"].astype(jnp.float32))  # [G, g, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k dispatch with per-expert positions (GShard's cumsum trick);
    # experts fill in routing-priority order, one chosen expert at a time
    combine = jnp.zeros((G, g, E, C), jnp.float32)
    dispatch_total = jnp.zeros((G, g, E), jnp.float32)
    fill = jnp.zeros((G, E), jnp.float32)   # per-group expert fill level
    remaining = probs
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                # [G, g]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [G, g, E]
        gate = jnp.sum(probs * onehot, axis=-1)             # [G, g]
        pos = (jnp.cumsum(onehot, axis=1) - 1.0) + fill[:, None, :]
        pos = jnp.sum(pos * onehot, axis=-1)                # [G, g]
        keep = (pos < C).astype(jnp.float32) * jnp.sum(onehot, -1)
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                                dtype=jnp.float32)          # [G, g, C]
        combine = combine + (gate * keep)[..., None, None] \
            * onehot[..., None] * pos_oh[..., None, :]
        dispatch_total = dispatch_total + onehot * keep[..., None]
        fill = fill + jnp.sum(onehot * keep[..., None], axis=1)
        remaining = remaining * (1.0 - onehot)              # mask chosen

    # normalize top-k gates so kept weights sum to 1 per token
    denom = jnp.sum(combine, axis=(2, 3), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    dispatch = (combine > 0.0).astype(cfg.dtype)            # [G, g, E, C]

    # per-expert buffers; pinned to the ep axis so GSPMD lowers the
    # dispatch/combine einsums to all-to-alls over ICI
    expert_in = jnp.einsum("gtec,gtd->egcd", dispatch,
                           xg.astype(cfg.dtype))            # [E, G, C, D]
    if mesh is not None and "ep" in mesh.shape:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P("ep", None, None, None)))
    gate_h = jax.nn.silu(jnp.einsum(
        "egcd,edf->egcf", expert_in, layer["w1"].astype(cfg.dtype)))
    up = jnp.einsum("egcd,edf->egcf", expert_in,
                    layer["w3"].astype(cfg.dtype))
    expert_out = jnp.einsum("egcf,efd->egcd", gate_h * up,
                            layer["w2"].astype(cfg.dtype))  # [E, G, C, D]
    if mesh is not None and "ep" in mesh.shape:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P("ep", None, None, None)))
    out = jnp.einsum("gtec,egcd->gtd", combine.astype(cfg.dtype),
                     expert_out)

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e, where
    # f_e = fraction of tokens dispatched to e, p_e = mean router prob
    f = jnp.mean(dispatch_total, axis=(0, 1))
    p = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(f * p)

    return out.reshape(B, S, D), aux
